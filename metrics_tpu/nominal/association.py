"""Nominal association metrics on the streamed contingency matrix."""
from typing import Any, Callable, Optional

import numpy as np
import jax.numpy as jnp
from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.clustering import _contingency
from metrics_tpu.functional.nominal import (
    _cramers_v_compute,
    _pearson_cc_compute,
    _theils_u_compute,
    _tschuprows_t_compute,
)


class _AssociationMetric(Metric):
    """Shared base: stream the (preds-classes, target-classes) contingency."""

    def __init__(
        self,
        num_classes_preds: int,
        num_classes_target: Optional[int] = None,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ):
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
        )
        if num_classes_target is None:
            num_classes_target = num_classes_preds
        for name, v in (("num_classes_preds", num_classes_preds), ("num_classes_target", num_classes_target)):
            if not isinstance(v, int) or v < 1:
                raise ValueError(f"`{name}` must be a positive int, got {v!r}")
        self.num_classes_preds = num_classes_preds
        self.num_classes_target = num_classes_target
        self.add_state(
            "contingency",
            default=np.zeros((num_classes_preds, num_classes_target), dtype=np.int32),
            dist_reduce_fx="sum",
        )

    def update(self, preds: Array, target: Array) -> None:
        self.contingency = self.contingency + _contingency(
            preds, target, self.num_classes_preds, self.num_classes_target
        )

    def _score(self, cont: Array) -> Array:
        raise NotImplementedError

    def compute(self) -> Array:
        return self._score(self.contingency)


class CramersV(_AssociationMetric):
    """Accumulated Cramer's V (``scipy.stats.contingency.association``,
    ``method='cramer'``; optional Bergsma bias correction).

    Example:
        >>> import jax.numpy as jnp
        >>> metric = CramersV(num_classes_preds=3)
        >>> round(float(metric(jnp.array([0, 0, 1, 1, 2, 2]), jnp.array([0, 0, 1, 1, 2, 2]))), 4)
        1.0
    """

    def __init__(self, num_classes_preds: int, num_classes_target: Optional[int] = None,
                 bias_correction: bool = False, **kwargs: Any):
        super().__init__(num_classes_preds, num_classes_target, **kwargs)
        self.bias_correction = bias_correction

    def _score(self, cont: Array) -> Array:
        return _cramers_v_compute(cont, self.bias_correction)


class PearsonsContingencyCoefficient(_AssociationMetric):
    """Accumulated Pearson's contingency coefficient
    (``scipy.stats.contingency.association``, ``method='pearson'``).

    Example:
        >>> import jax.numpy as jnp
        >>> metric = PearsonsContingencyCoefficient(num_classes_preds=2)
        >>> round(float(metric(jnp.array([0, 0, 1, 1]), jnp.array([0, 0, 1, 1]))), 4)
        0.7071
    """

    def _score(self, cont: Array) -> Array:
        return _pearson_cc_compute(cont)


class TschuprowsT(_AssociationMetric):
    """Accumulated Tschuprow's T
    (``scipy.stats.contingency.association``, ``method='tschuprow'``).

    Example:
        >>> import jax.numpy as jnp
        >>> metric = TschuprowsT(num_classes_preds=3)
        >>> round(float(metric(jnp.array([0, 0, 1, 1, 2, 2]), jnp.array([0, 0, 1, 1, 2, 2]))), 4)
        1.0
    """

    def _score(self, cont: Array) -> Array:
        return _tschuprows_t_compute(cont)


class TheilsU(_AssociationMetric):
    """Accumulated Theil's U — asymmetric: how much knowing ``preds``
    reduces the entropy of ``target``.

    Example:
        >>> import jax.numpy as jnp
        >>> metric = TheilsU(num_classes_preds=2)
        >>> round(float(metric(jnp.array([0, 0, 1, 1]), jnp.array([0, 0, 1, 1]))), 4)
        1.0
    """

    def _score(self, cont: Array) -> Array:
        return _theils_u_compute(cont)
