"""Object-detection metrics (an extension family; later torchmetrics ships ``detection/``).

``MeanAveragePrecision`` accumulates per-image padded box sets and runs the
COCO evaluation as one static-shape jittable program — see
``metrics_tpu/functional/detection/map.py`` for the engine.
"""
from metrics_tpu.detection.mean_ap import MeanAveragePrecision

__all__ = ["MeanAveragePrecision"]
