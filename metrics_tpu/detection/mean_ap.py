"""MeanAveragePrecision module (full COCO semantics, TPU-native engine).

Result-dict key parity with later torchmetrics ``detection/mean_ap.py``:
``map``, ``map_50``, ``map_75``, ``map_small/medium/large``,
``mar_1/10/100``, ``mar_small/medium/large``, plus per-class vectors under
``class_metrics``. Missing classes are ``nan`` (pycocotools' ``-1``
sentinel translated to the library-wide nan convention).
"""
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.detection.map import (
    COCO_AREA_RANGES,
    COCO_IOU_THRESHOLDS,
    COCO_MAX_DETS,
    coco_map_padded,
)
from metrics_tpu.parallel.buffer import as_values
from metrics_tpu.utils.prints import rank_zero_warn


class MeanAveragePrecision(Metric):
    """COCO-style mean average precision for object detection.

    ``update`` takes the torchmetrics-style per-image dict lists::

        preds  = [{"boxes": (N, 4) xyxy, "scores": (N,), "labels": (N,)}, ...]
        target = [{"boxes": (M, 4) xyxy, "labels": (M,),
                   "iscrowd": (M,) optional}, ...]

    Crowd ground truths use intersection-over-detection-area overlap, may
    match any number of detections, and are ignore-flagged (detections
    matched to them count neither as TP nor FP) — pycocotools semantics.

    Every image is padded to static ``max_detections`` / ``max_gt`` slots.
    ``max_detections`` is the static per-image CAPACITY (all classes
    together), not the COCO maxDets cap — the COCO caps are
    ``max_detection_thresholds``, applied per (image, class) inside the
    engine. An image exceeding the capacity keeps its top-scoring
    detections and a warning names the truncation; size ``max_detections``
    so that real images fit (pycocotools evaluates every detection). The
    states are per-image stacks (cat-states, so they shard and gather like
    every other epoch metric), and ``compute()`` runs the whole COCO
    evaluation as one static-shape jitted program: greedy matching scanned
    over detection slots, vmapped over images x classes x IoU thresholds x
    area ranges.

    Args:
        num_classes: static class count (labels in ``[0, num_classes)``).
        iou_thresholds: tuple of IoU thresholds (default COCO
            0.50:0.05:0.95).
        max_detections: static per-image detection CAPACITY across classes
            (default 100); overflow keeps the top scores and warns.
        max_gt: per-image ground-truth cap (exceeding it raises).
        max_detection_thresholds: the COCO ``maxDets`` recall caps, applied
            per (image, class) (default ``(1, 10, 100)``; keys ``mar_<k>``).
        class_metrics: include the per-class vectors in the result dict.

    Example:
        >>> import jax.numpy as jnp
        >>> metric = MeanAveragePrecision(num_classes=2)
        >>> preds = [{"boxes": jnp.array([[0.0, 0.0, 10.0, 10.0]]),
        ...           "scores": jnp.array([0.9]), "labels": jnp.array([0])}]
        >>> target = [{"boxes": jnp.array([[0.0, 0.0, 10.0, 10.0]]),
        ...            "labels": jnp.array([0])}]
        >>> out = metric(preds, target)
        >>> float(out["map"])
        1.0
    """

    def __init__(
        self,
        num_classes: int,
        iou_thresholds: Sequence[float] = COCO_IOU_THRESHOLDS,
        max_detections: int = 100,
        max_gt: int = 100,
        max_detection_thresholds: Sequence[int] = COCO_MAX_DETS,
        class_metrics: bool = False,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
        capacity: Optional[int] = None,
    ):
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
            capacity=capacity,
        )
        if not isinstance(num_classes, int) or num_classes < 1:
            raise ValueError(f"`num_classes` must be a positive int, got {num_classes!r}")
        if max_detections < 1 or max_gt < 1:
            raise ValueError("`max_detections` and `max_gt` must be positive")
        if not max_detection_thresholds or any(int(k) < 1 for k in max_detection_thresholds):
            raise ValueError("`max_detection_thresholds` must be positive ints")
        self.num_classes = num_classes
        self.iou_thresholds = tuple(float(t) for t in iou_thresholds)
        self.max_detections = max_detections
        self.max_gt = max_gt
        self.max_detection_thresholds = tuple(int(k) for k in max_detection_thresholds)
        self.class_metrics = class_metrics
        d, g = max_detections, max_gt
        self.add_state("det_boxes", default=[], dist_reduce_fx=None, item_shape=(d, 4))
        self.add_state("det_scores", default=[], dist_reduce_fx=None, item_shape=(d,))
        self.add_state("det_labels", default=[], dist_reduce_fx=None, item_shape=(d,), item_dtype=jnp.int32)
        self.add_state("det_valid", default=[], dist_reduce_fx=None, item_shape=(d,), item_dtype=jnp.bool_)
        self.add_state("gt_boxes", default=[], dist_reduce_fx=None, item_shape=(g, 4))
        self.add_state("gt_labels", default=[], dist_reduce_fx=None, item_shape=(g,), item_dtype=jnp.int32)
        self.add_state("gt_valid", default=[], dist_reduce_fx=None, item_shape=(g,), item_dtype=jnp.bool_)
        self.add_state("gt_crowd", default=[], dist_reduce_fx=None, item_shape=(g,), item_dtype=jnp.bool_)

    def _pad_det(self, entry: Dict[str, Array]) -> Tuple[Array, Array, Array, Array]:
        boxes = jnp.asarray(entry["boxes"], dtype=jnp.float32).reshape(-1, 4)
        scores = jnp.asarray(entry["scores"], dtype=jnp.float32).reshape(-1)
        labels = jnp.asarray(entry["labels"], dtype=jnp.int32).reshape(-1)
        if not (boxes.shape[0] == scores.shape[0] == labels.shape[0]):
            raise ValueError(
                f"boxes/scores/labels disagree: {boxes.shape[0]}/{scores.shape[0]}/{labels.shape[0]}"
            )
        n, cap = boxes.shape[0], self.max_detections
        if n > cap:
            # static-capacity overflow: keep the top-scoring `cap` detections
            # ACROSS classes. This can drop detections pycocotools (whose
            # maxDets caps are per class) would keep — hence the loud notice.
            rank_zero_warn(
                f"MeanAveragePrecision: image with {n} detections truncated to"
                f" max_detections={cap} (top scores across classes); raise"
                " `max_detections` to evaluate every detection as pycocotools does."
            )
            keep = jnp.argsort(-scores)[:cap]
            boxes, scores, labels, n = boxes[keep], scores[keep], labels[keep], cap
        pad = cap - n
        return (
            jnp.pad(boxes, ((0, pad), (0, 0))),
            jnp.pad(scores, (0, pad)),
            jnp.pad(labels, (0, pad)),
            jnp.pad(jnp.ones(n, dtype=bool), (0, pad)),
        )

    def _pad_gt(self, entry: Dict[str, Array]) -> Tuple[Array, Array, Array, Array]:
        boxes = jnp.asarray(entry["boxes"], dtype=jnp.float32).reshape(-1, 4)
        labels = jnp.asarray(entry["labels"], dtype=jnp.int32).reshape(-1)
        if boxes.shape[0] != labels.shape[0]:
            raise ValueError(f"gt boxes/labels disagree: {boxes.shape[0]}/{labels.shape[0]}")
        crowd = entry.get("iscrowd")
        crowd = (
            jnp.zeros(labels.shape[0], dtype=bool)
            if crowd is None
            else jnp.asarray(crowd).reshape(-1).astype(bool)
        )
        if crowd.shape[0] != labels.shape[0]:
            raise ValueError(f"gt iscrowd/labels disagree: {crowd.shape[0]}/{labels.shape[0]}")
        n, cap = boxes.shape[0], self.max_gt
        if n > cap:
            raise ValueError(f"image has {n} ground-truth boxes > max_gt={cap}")
        pad = cap - n
        return (
            jnp.pad(boxes, ((0, pad), (0, 0))),
            jnp.pad(labels, (0, pad)),
            jnp.pad(jnp.ones(n, dtype=bool), (0, pad)),
            jnp.pad(crowd, (0, pad)),
        )

    def update(self, preds: List[Dict[str, Array]], target: List[Dict[str, Array]]) -> None:
        if len(preds) != len(target):
            raise ValueError(f"preds has {len(preds)} images, target {len(target)}")
        for det_entry, gt_entry in zip(preds, target):
            db, ds, dl, dv = self._pad_det(det_entry)
            gb, gl, gv, gc = self._pad_gt(gt_entry)
            self._append("det_boxes", db[None])
            self._append("det_scores", ds[None])
            self._append("det_labels", dl[None])
            self._append("det_valid", dv[None])
            self._append("gt_boxes", gb[None])
            self._append("gt_labels", gl[None])
            self._append("gt_valid", gv[None])
            self._append("gt_crowd", gc[None])

    def compute(self) -> Dict[str, Array]:
        k_largest = max(self.max_detection_thresholds)
        per_class_keys = ("map_per_class", f"mar_{k_largest}_per_class")
        raw = self.det_boxes
        empty = isinstance(raw, (list, tuple)) and len(raw) == 0
        det_boxes = None if empty else as_values(raw)
        if empty or det_boxes.shape[0] == 0:
            nan = jnp.asarray(jnp.nan)
            out = {"map": nan, "map_50": nan, "map_75": nan}
            for k in self.max_detection_thresholds:
                out[f"mar_{k}"] = nan
            for name, _, _ in COCO_AREA_RANGES[1:]:
                out[f"map_{name}"] = nan
                out[f"mar_{name}"] = nan
            if self.class_metrics:
                for key in per_class_keys:
                    out[key] = jnp.full((self.num_classes,), jnp.nan)
            return out
        args = (
            det_boxes,
            as_values(self.det_scores),
            as_values(self.det_labels),
            as_values(self.det_valid),
            as_values(self.gt_boxes),
            as_values(self.gt_labels),
            as_values(self.gt_valid),
        )
        fn = coco_map_padded
        if self._jit is not False and not self._jit_failed:
            fn = jax.jit(
                coco_map_padded,
                static_argnames=("num_classes", "iou_thresholds", "max_detection_thresholds"),
            )
        out = fn(
            *args,
            num_classes=self.num_classes,
            iou_thresholds=self.iou_thresholds,
            gt_crowd=as_values(self.gt_crowd),
            max_detection_thresholds=self.max_detection_thresholds,
        )
        if not self.class_metrics:
            out = {k: v for k, v in out.items() if k not in per_class_keys}
        return out
