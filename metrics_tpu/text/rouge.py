"""ROUGEScore module. Extension beyond the reference snapshot (later
torchmetrics ``text/rouge.py``).

Streams the per-sentence precision/recall/F1 sums per rouge key plus a pair
count — nine-plus-one scalar ``"sum"`` states, so the accumulated value is
the mean of per-sentence scores over everything seen (the rouge_score
aggregation convention) and sync is one summed reduction.
"""
from typing import Any, Callable, Dict, Optional, Sequence, Union

import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.text_rouge import ROUGE_KEYS, _batch_sums, _check_rouge_keys
from metrics_tpu.utils.data import accum_int_dtype

_STATS = ("precision", "recall", "fmeasure")


class ROUGEScore(Metric):
    r"""Accumulated ROUGE-N / ROUGE-L scores (mean of per-sentence values).

    Example:
        >>> metric = ROUGEScore(rouge_keys=("rouge1",))
        >>> out = metric(["the cat sat on the mat"], ["the cat was on the mat"])
        >>> round(float(out["rouge1_fmeasure"]), 4)
        0.8333
    """

    def __init__(
        self,
        rouge_keys: Sequence[str] = ROUGE_KEYS,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ):
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
            jit=False,  # update consumes host strings; the fused step cannot trace them
        )
        self.rouge_keys = _check_rouge_keys(rouge_keys)
        for key in self.rouge_keys:
            for stat in _STATS:
                self.add_state(f"{key}_{stat}_sum", default=np.zeros(()), dist_reduce_fx="sum")
        self.add_state("pairs", default=np.zeros((), dtype=accum_int_dtype()), dist_reduce_fx="sum")

    def update(self, preds: Union[str, Sequence[str]], target: Union[str, Sequence[str]]) -> None:
        sums, n = _batch_sums(preds, target, self.rouge_keys)
        self.note_count(n)
        for key, values in sums.items():
            for stat, value in zip(_STATS, values):
                name = f"{key}_{stat}_sum"
                setattr(self, name, getattr(self, name) + value)
        self.pairs = self.pairs + n

    def compute(self) -> Dict[str, Array]:
        n = jnp.maximum(self.pairs, 1).astype(jnp.float32)
        return {
            f"{key}_{stat}": getattr(self, f"{key}_{stat}_sum") / n
            for key in self.rouge_keys
            for stat in _STATS
        }
