"""Perplexity module. Extension beyond the reference snapshot (later
torchmetrics ``text/perplexity.py``)."""
from typing import Any, Callable, Optional, Tuple

import jax.numpy as jnp
from jax import Array

from metrics_tpu.core.streaming import SumCountMetric
from metrics_tpu.functional.text_perplexity import _perplexity_update


class Perplexity(SumCountMetric):
    r"""Accumulated perplexity: ``exp`` of the mean token NLL over all
    tokens seen (two scalar sum-states; one psum to sync).

    Args:
        ignore_index: target id excluded from the likelihood (padding).

    Example:
        >>> import jax.numpy as jnp
        >>> logits = jnp.log(jnp.array([[[0.25, 0.75], [0.5, 0.5]]]))
        >>> metric = Perplexity()
        >>> round(float(metric(logits, jnp.array([[1, 0]]))), 4)
        1.633
    """

    def __init__(
        self,
        ignore_index: Optional[int] = None,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ):
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
        )
        if ignore_index is not None and not isinstance(ignore_index, int):
            raise ValueError(f"`ignore_index` must be an int or None, got {ignore_index!r}")
        self.ignore_index = ignore_index

    def _update_stats(self, preds: Array, target: Array) -> Tuple[Array, Any]:
        return _perplexity_update(preds, target, self.ignore_index)

    def _finalize(self, mean: Array) -> Array:
        return jnp.exp(mean)
