from metrics_tpu.text.wer import WER
from metrics_tpu.text.error_rates import (
    CharErrorRate,
    MatchErrorRate,
    WordInfoLost,
    WordInfoPreserved,
)
