from metrics_tpu.text.wer import WER
