from metrics_tpu.text.wer import WER
from metrics_tpu.text.error_rates import (
    CharErrorRate,
    MatchErrorRate,
    WordInfoLost,
    WordInfoPreserved,
)
from metrics_tpu.text.perplexity import Perplexity
from metrics_tpu.text.bleu import BLEUScore, SacreBLEUScore
from metrics_tpu.text.chrf import CHRFScore
from metrics_tpu.text.edit import EditDistance
from metrics_tpu.text.rouge import ROUGEScore
from metrics_tpu.text.squad import SQuAD
from metrics_tpu.text.ter import TranslationEditRate
