"""BLEUScore / SacreBLEUScore modules. Extension beyond the reference
snapshot (later torchmetrics ``text/bleu.py`` / ``text/sacre_bleu.py``;
the reference ships only the functional ``bleu_score``, nlp.py:70-126).

The sufficient statistics — per-order clipped matches and totals plus the
translation/reference length sums — are all ``"sum"``-reducible, so the
accumulated value is the true CORPUS BLEU of everything seen (not a mean of
batch scores) and sync is one summed reduction. Counting runs on device
(``functional/nlp.py::bleu_counts``); only tokenization is host-side.
"""
from typing import Any, Callable, List, Optional, Sequence, Union

import numpy as np
import jax.numpy as jnp
from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.nlp import _intern_corpus, _pad_corpus, bleu_counts, bleu_from_counts
from metrics_tpu.functional.text_sacrebleu import TOKENIZERS, tokenize_sacrebleu
from metrics_tpu.utils.data import accum_int_dtype

TokenizedOrRaw = Union[str, Sequence[str]]


class BLEUScore(Metric):
    """Accumulated corpus BLEU.

    ``update`` takes hypothesis sentences and per-hypothesis reference
    lists; raw strings are whitespace-split (pass pre-tokenized lists to
    control tokenization, or use :class:`SacreBLEUScore`).

    Example:
        >>> metric = BLEUScore()
        >>> preds = ["the cat is on the mat"]
        >>> target = [["there is a cat on the mat", "a cat is on the mat"]]
        >>> round(float(metric(preds, target)), 4)
        0.7598
    """

    def __init__(
        self,
        n_gram: int = 4,
        smooth: bool = False,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ):
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
            jit=False,  # update consumes host strings; the fused step cannot trace them
        )
        if not isinstance(n_gram, int) or n_gram < 1:
            raise ValueError(f"`n_gram` must be a positive int, got {n_gram!r}")
        self.n_gram = n_gram
        self.smooth = smooth
        # numerator is fractional (clipped-match ratios) and bounded by the
        # integer denominator; the count-like states use the int accumulator
        # dtype so the int32-overflow warning machinery covers them
        self.add_state("numerator", default=np.zeros(n_gram), dist_reduce_fx="sum")
        self.add_state("denominator", default=np.zeros(n_gram, dtype=accum_int_dtype()), dist_reduce_fx="sum")
        self.add_state("trans_len", default=np.zeros((), dtype=accum_int_dtype()), dist_reduce_fx="sum")
        self.add_state("ref_len", default=np.zeros((), dtype=accum_int_dtype()), dist_reduce_fx="sum")

    def _tok(self, text: TokenizedOrRaw) -> List[str]:
        return text.split() if isinstance(text, str) else list(text)

    def update(self, preds: Sequence[TokenizedOrRaw], target: Sequence[Sequence[TokenizedOrRaw]]) -> None:
        if len(preds) != len(target):
            raise ValueError(f"preds has {len(preds)} sentences, target {len(target)}")
        hyps = [self._tok(p) for p in preds]
        refs = [[self._tok(r) for r in rs] for rs in target]
        hyp_ids, ref_ids = _intern_corpus(hyps, refs)
        num, den, c, r = bleu_counts(*_pad_corpus(hyp_ids, ref_ids), n_gram=self.n_gram)
        # feed the int32-overflow warning a bound that dominates EVERY int
        # state increment: denominator/trans_len grow by hyp tokens, ref_len
        # by the closest-reference lengths (bounded by the longest ref)
        self.note_count(max(
            sum(len(h) for h in hyps),
            sum(max((len(r) for r in rs), default=0) for rs in refs),
        ))
        self.numerator = self.numerator + num
        self.denominator = self.denominator + den.astype(self.denominator.dtype)
        self.trans_len = self.trans_len + c.astype(self.trans_len.dtype)
        self.ref_len = self.ref_len + r.astype(self.ref_len.dtype)

    def compute(self) -> Array:
        return bleu_from_counts(
            jnp.asarray(self.numerator, dtype=jnp.float32),
            jnp.asarray(self.denominator, dtype=jnp.float32),
            jnp.asarray(self.trans_len, dtype=jnp.float32),
            jnp.asarray(self.ref_len, dtype=jnp.float32),
            smooth=self.smooth,
        )


class SacreBLEUScore(BLEUScore):
    """Corpus BLEU over RAW strings with sacrebleu tokenization (default
    mteval-v13a); otherwise identical statistics and aggregation to
    :class:`BLEUScore`.

    Example:
        >>> metric = SacreBLEUScore()
        >>> preds = ["the cat is on the mat"]
        >>> target = [["there is a cat on the mat", "a cat is on the mat"]]
        >>> round(float(metric(preds, target)), 4)
        0.7598
    """

    def __init__(self, n_gram: int = 4, smooth: bool = False, tokenize: str = "13a",
                 lowercase: bool = False, **kwargs: Any):
        super().__init__(n_gram=n_gram, smooth=smooth, **kwargs)
        if tokenize not in TOKENIZERS:
            raise ValueError(f"`tokenize` must be one of {TOKENIZERS}, got {tokenize!r}")
        self.tokenize = tokenize
        self.lowercase = lowercase

    def _tok(self, text: TokenizedOrRaw) -> List[str]:
        if not isinstance(text, str):
            text = " ".join(text)
        return tokenize_sacrebleu(text, self.tokenize, self.lowercase)
