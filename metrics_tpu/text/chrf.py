"""CHRFScore module. Extension beyond the reference snapshot (later
torchmetrics ``text/chrf.py``; sacrebleu chrF2 conventions — see
``functional/text_chrf.py``)."""
from typing import Any, Callable, Optional, Sequence, Union

import numpy as np
from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.text_chrf import CHRF_CHAR_ORDER, chrf_from_stats, chrf_stats
from metrics_tpu.utils.data import accum_int_dtype


class CHRFScore(Metric):
    """Accumulated corpus chrF: per-order character n-gram statistics sum
    across updates (and processes/mesh axes), the F-score computes from the
    corpus totals — the sacrebleu aggregation.

    Example:
        >>> metric = CHRFScore()
        >>> round(float(metric(["the cat sat"], ["the cat sat"])), 4)
        1.0
    """

    def __init__(
        self,
        n_char_order: int = CHRF_CHAR_ORDER,
        beta: float = 2.0,
        lowercase: bool = False,
        whitespace: bool = False,
        eps_smoothing: bool = False,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ):
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
            jit=False,  # update consumes host strings; the fused step cannot trace them
        )
        if not isinstance(n_char_order, int) or n_char_order < 1:
            raise ValueError(f"`n_char_order` must be a positive int, got {n_char_order!r}")
        if beta <= 0:
            raise ValueError(f"`beta` must be positive, got {beta!r}")
        self.n_char_order = n_char_order
        self.beta = float(beta)
        self.lowercase = lowercase
        self.whitespace = whitespace
        self.eps_smoothing = eps_smoothing
        self.add_state(
            "stats", default=np.zeros((3, n_char_order), dtype=accum_int_dtype()), dist_reduce_fx="sum"
        )

    def update(self, preds: Union[str, Sequence[str]], target: Union[str, Sequence[str]]) -> None:
        batch = chrf_stats(preds, target, self.n_char_order, self.lowercase, self.whitespace)
        # each update adds up to max(batch) to an int count state — feed the
        # int32-overflow warning the real bound (siblings: ROUGE/WER/SQuAD)
        self.note_count(int(batch.max()))
        self.stats = self.stats + batch

    def compute(self) -> Array:
        import jax.numpy as jnp

        return jnp.asarray(
            chrf_from_stats(np.asarray(self.stats), self.beta, self.eps_smoothing),
            dtype=jnp.float32,
        )
