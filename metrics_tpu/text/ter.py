"""TranslationEditRate module. Extension beyond the reference snapshot
(later torchmetrics ``text/ter.py``; Tercom semantics — see
``functional/text_ter.py``)."""
from typing import Any, Callable, Optional, Sequence, Union

import numpy as np
import jax.numpy as jnp
from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.text_ter import ter_from_stats, ter_stats


class TranslationEditRate(Metric):
    """Accumulated corpus TER: per-segment best edit counts (shifts +
    Levenshtein, minimum over references) and average reference lengths sum
    across updates, the rate computes from the corpus totals — the
    Tercom/sacrebleu aggregation. Lower is better.

    Example:
        >>> metric = TranslationEditRate()
        >>> round(float(metric(["the cat sat on mat"],
        ...                    [["the cat sat on the mat"]])), 4)
        0.1667
    """

    def __init__(
        self,
        case_sensitive: bool = False,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ):
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
            jit=False,  # update consumes host strings; the fused step cannot trace them
        )
        self.case_sensitive = case_sensitive
        self.add_state("total_edits", default=np.zeros(()), dist_reduce_fx="sum")
        self.add_state("total_ref_len", default=np.zeros(()), dist_reduce_fx="sum")

    def update(self, preds: Union[str, Sequence[str]], target: Sequence[Sequence[str]]) -> None:
        edits, ref_len = ter_stats(preds, target, self.case_sensitive)
        self.total_edits = self.total_edits + edits
        self.total_ref_len = self.total_ref_len + ref_len

    def compute(self) -> Array:
        return jnp.asarray(
            ter_from_stats(float(self.total_edits), float(self.total_ref_len)),
            dtype=jnp.float32,
        )
