"""CharErrorRate / MatchErrorRate / WordInfoPreserved / WordInfoLost modules.

Extension beyond the reference snapshot (later torchmetrics ships these in
its text package). All stream through integer sum-states of the per-pair
alignment statistics (edit errors, aligned hits, reference/prediction
lengths), so accumulation is O(1) and sync is one summed reduction — the
global value equals the value over the concatenated corpus.
"""
from typing import Any, Callable, Optional, Sequence, Union

import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.text import TokenSeq, _chars, _sequence_stats, _tokens
from metrics_tpu.utils.data import accum_int_dtype


class _AlignmentStatsMetric(Metric):
    """Accumulates (errors, hits, target len, pred len) over sequence pairs."""

    _tokenize = staticmethod(_tokens)
    _need_hits = True

    def __init__(
        self,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ):
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
            jit=False,  # update consumes host strings; the fused step cannot trace them
        )
        for name in ("errors", "hits", "total_target", "total_pred"):
            self.add_state(name, default=np.zeros((), dtype=accum_int_dtype()), dist_reduce_fx="sum")

    def update(self, preds: Union[TokenSeq, Sequence[TokenSeq]], target: Union[TokenSeq, Sequence[TokenSeq]]) -> None:
        errors, hits, total_t, total_p = _sequence_stats(preds, target, self._tokenize, self._need_hits)
        self.note_count(max(errors, hits, total_t, total_p))
        self.errors = self.errors + errors
        self.hits = self.hits + hits
        self.total_target = self.total_target + total_t
        self.total_pred = self.total_pred + total_p


class CharErrorRate(_AlignmentStatsMetric):
    r"""Accumulated character error rate (edit distance over characters /
    reference characters; spaces count as characters).

    Example:
        >>> metric = CharErrorRate()
        >>> float(metric(["abcd"], ["abce"]))
        0.25
    """

    _tokenize = staticmethod(_chars)
    _need_hits = False  # CER needs only the distance; skip the tuple DP

    def compute(self) -> Array:
        rate = self.errors.astype(jnp.float32) / jnp.maximum(self.total_target, 1).astype(jnp.float32)
        return jnp.where(
            self.total_target == 0, jnp.where(self.errors == 0, 0.0, jnp.inf), rate
        )


class MatchErrorRate(_AlignmentStatsMetric):
    r"""Accumulated match error rate: ``(S+D+I) / (H+S+D+I)`` over all pairs.

    Example:
        >>> metric = MatchErrorRate()
        >>> float(metric(["the cat sat"], ["the cat sat on the mat"]))
        0.5
    """

    def compute(self) -> Array:
        denom = (self.errors + self.hits).astype(jnp.float32)
        return jnp.where(denom == 0, 0.0, self.errors.astype(jnp.float32) / jnp.maximum(denom, 1.0))


class WordInfoPreserved(_AlignmentStatsMetric):
    r"""Accumulated word information preserved: ``(H/N_target) * (H/N_pred)``.

    Example:
        >>> metric = WordInfoPreserved()
        >>> float(metric(["the cat sat"], ["the cat sat on the mat"]))
        0.5
    """

    def compute(self) -> Array:
        h = self.hits.astype(jnp.float32)
        nt = jnp.maximum(self.total_target, 1).astype(jnp.float32)
        np_ = jnp.maximum(self.total_pred, 1).astype(jnp.float32)
        return jnp.where((self.total_target == 0) | (self.total_pred == 0), 0.0, (h / nt) * (h / np_))


class WordInfoLost(WordInfoPreserved):
    r"""Accumulated word information lost: ``1 - WIP``.

    Example:
        >>> metric = WordInfoLost()
        >>> float(metric(["the cat sat"], ["the cat sat on the mat"]))
        0.5
    """

    def compute(self) -> Array:
        return 1.0 - super().compute()
