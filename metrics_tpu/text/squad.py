"""SQuAD module. Extension beyond the reference snapshot (later torchmetrics
``text/squad.py``). Streams best-over-references EM and F1 sums plus a
question count — the accumulated value equals the official script over the
concatenated dataset."""
from typing import Any, Callable, Dict, Optional, Sequence, Union

import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.text_squad import _squad_batch_sums
from metrics_tpu.utils.data import accum_int_dtype


class SQuAD(Metric):
    r"""Accumulated SQuAD exact-match / F1 (percentages, official semantics).

    Example:
        >>> metric = SQuAD()
        >>> out = metric(["the cat"], [["The cat!", "a dog"]])
        >>> (float(out["exact_match"]), float(out["f1"]))
        (100.0, 100.0)
    """

    def __init__(
        self,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ):
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
            jit=False,  # update consumes host strings; the fused step cannot trace them
        )
        self.add_state("em_sum", default=np.zeros(()), dist_reduce_fx="sum")
        self.add_state("f1_sum", default=np.zeros(()), dist_reduce_fx="sum")
        self.add_state("questions", default=np.zeros((), dtype=accum_int_dtype()), dist_reduce_fx="sum")

    def update(
        self,
        preds: Union[str, Sequence[str]],
        target: Union[str, Sequence[str], Sequence[Sequence[str]]],
    ) -> None:
        em_sum, f1_sum, n = _squad_batch_sums(preds, target)
        self.note_count(n)
        self.em_sum = self.em_sum + em_sum
        self.f1_sum = self.f1_sum + f1_sum
        self.questions = self.questions + n

    def compute(self) -> Dict[str, Array]:
        n = jnp.maximum(self.questions, 1).astype(jnp.float32)
        return {"exact_match": 100.0 * self.em_sum / n, "f1": 100.0 * self.f1_sum / n}
