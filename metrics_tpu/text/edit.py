"""EditDistance module. Extension beyond the reference snapshot (later
torchmetrics ``text/edit.py``); the functional form is
``metrics_tpu.functional.edit_distance``."""
from typing import Any, Callable, Optional, Sequence, Union

import numpy as np
import jax.numpy as jnp
from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.text import _np_edit_distance
from metrics_tpu.utils.data import accum_int_dtype


class EditDistance(Metric):
    """Accumulated character-level edit distance over all sentence pairs
    seen (``reduction="mean"``: total distance / total pairs; ``"sum"``:
    total distance). Two scalar sum-states — streams and sum-syncs.

    Example:
        >>> metric = EditDistance()
        >>> float(metric(["abcd"], ["abce"]))
        1.0
    """

    def __init__(
        self,
        reduction: str = "mean",
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ):
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
            jit=False,  # update consumes host strings; the fused step cannot trace them
        )
        if reduction not in ("mean", "sum"):
            raise ValueError(f"`reduction` must be 'mean' or 'sum', got {reduction!r}")
        self.reduction = reduction
        self.add_state("total_distance", default=np.zeros((), dtype=accum_int_dtype()), dist_reduce_fx="sum")
        self.add_state("pairs", default=np.zeros((), dtype=accum_int_dtype()), dist_reduce_fx="sum")

    def update(self, preds: Union[str, Sequence[str]], target: Union[str, Sequence[str]]) -> None:
        preds = [preds] if isinstance(preds, str) else list(preds)
        target = [target] if isinstance(target, str) else list(target)
        if len(preds) != len(target):
            raise ValueError(f"preds has {len(preds)} sentences, target {len(target)}")
        batch = sum(_np_edit_distance(list(p), list(t)) for p, t in zip(preds, target))
        # bound on what this update ADDS to the int states: distance per pair
        # is at most max(len(p), len(t)), summed over the batch
        self.note_count(sum(max(len(p), len(t)) for p, t in zip(preds, target)))
        self.total_distance = self.total_distance + batch
        self.pairs = self.pairs + len(preds)

    def compute(self) -> Array:
        total = jnp.asarray(self.total_distance, dtype=jnp.float32)
        if self.reduction == "sum":
            return total
        pairs = jnp.asarray(self.pairs, dtype=jnp.float32)
        return jnp.where(pairs == 0, jnp.nan, total / jnp.maximum(pairs, 1.0))
