"""WER module. Extension beyond the reference snapshot.

Streams through two scalar sum-states (edit errors / reference words), so
accumulation is O(1) and cross-process sync is one summed reduction.
"""
from typing import Any, Callable, Optional, Sequence, Union

import numpy as np
import jax.numpy as jnp
from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.text import TokenSeq, _wer_update
from metrics_tpu.utils.data import accum_int_dtype


class WER(Metric):
    r"""Accumulated word error rate over sequence pairs.

    Accepts strings (whitespace-tokenized) or pre-tokenized sequences, and
    also pre-computed device results via ``update_counts`` for pipelines that
    run the batched on-device edit-distance kernel
    (``functional.edit_distance_padded``).

    Example:
        >>> metric = WER()
        >>> float(metric(["the cat sat"], ["the cat sat on the mat"]))
        0.5
    """

    def __init__(
        self,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ):
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
            jit=False,  # update consumes host strings; the fused jit step cannot trace them
        )
        self.add_state("errors", default=np.zeros((), dtype=accum_int_dtype()), dist_reduce_fx="sum")
        self.add_state("total", default=np.zeros((), dtype=accum_int_dtype()), dist_reduce_fx="sum")

    def update(self, preds: Union[TokenSeq, Sequence[TokenSeq]], target: Union[TokenSeq, Sequence[TokenSeq]]) -> None:
        errors, total = _wer_update(preds, target)
        # host inputs (strings/token lists) carry no .size for the automatic
        # bound; the counts are host ints here, so advance it exactly
        self.note_count(max(int(errors), int(total)))
        self.errors = self.errors + errors
        self.total = self.total + total

    def update_counts(self, errors: Array, ref_words: Array) -> None:
        """Accumulate pre-computed device counts (e.g. from
        ``edit_distance_padded`` distances and target lengths).

        The counts live on device, so the int32-overflow bound can only be
        advanced by the sequence count here; when you know the padded
        sequence length ``M``, call ``self.note_count(B * M)`` yourself for
        a tight bound (reference words per sequence are ≤ M).
        """
        self._computed = None  # bypasses the wrapped update, so drop its cache here
        self.note_count(int(ref_words.size))
        self.errors = self.errors + jnp.sum(errors)
        self.total = self.total + jnp.sum(ref_words)

    def compute(self) -> Array:
        # empty reference: 0.0 for a perfect empty match, inf when there are
        # errors (matching the functional)
        rate = self.errors.astype(jnp.float32) / jnp.maximum(self.total, 1).astype(jnp.float32)
        return jnp.where(
            self.total == 0, jnp.where(self.errors == 0, 0.0, jnp.inf), rate
        )
