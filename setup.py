#!/usr/bin/env python
"""Packaging for metrics_tpu (reference L0: setup.py + torchmetrics/info.py)."""
import os

from setuptools import find_packages, setup

_PATH_ROOT = os.path.dirname(__file__)


def _load_py_module(fname: str):
    import importlib.util

    spec = importlib.util.spec_from_file_location("info", os.path.join(_PATH_ROOT, "metrics_tpu", fname))
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


info = _load_py_module("info.py")

setup(
    name="metrics_tpu",
    version=info.__version__,
    description=info.__docs__,
    author=info.__author__,
    license=info.__license__,
    packages=find_packages(exclude=["tests", "tests.*"]),
    python_requires=">=3.9",
    install_requires=["jax>=0.4.30", "numpy"],
    extras_require={"test": ["pytest", "scikit-learn", "scipy", "nltk"]},
)
